"""Zero-knob adaptive capacity (ISSUE 18).

Pins the tentpole contracts:

  * `BatchedDeviceNFA.resize()` golden cycles -- grow -> shrink -> grow
    back to the arm shape preserves matches (Sequence equality covers
    events and fold values), emission-identity digests, and the final
    state/pool trees BITWISE, on both step engines (xla +
    pallas_interpret) and both drain modes (flat + pool); also mid
    gc-group (G > 1) and under an armed EventTimeGate;
  * cross-shape restore refuses loudly (`ShapeRestoreError`) when live
    occupancy exceeds the target shape -- never silent truncation --
    and the `CapacityAutosizer` converts a refused shrink into a
    counted no-op, not a crash;
  * the autosizer control law: drop-reactive doubling (a match drop
    doubles `matches_per_step` alongside `matches` -- the counter
    cannot tell ring pressure from the per-step emission cap),
    `ensure_page`'s admission guarantee, proactive grow behind the
    budget, patience shrink floored at the arm config, and
    `suggest_t()` riding the cadence controller (satellite 1: no dead
    public API);
  * `AdmissionPacer` pow2 pacing; `runtime="auto"` routing (host below
    the key threshold, promote on growth, digests identical to
    all-device);
  * the artifact plumbing both ways: `check_bench_schema` accepts the
    `autosize` block and `perf_ledger` excuses cross-`autosized`
    comparisons as `autosize_change`.
"""
import hashlib
import math
import os
import random
import sys
from dataclasses import replace

import pytest

from kafkastreams_cep_tpu import Event, QueryBuilder, compile_pattern
from kafkastreams_cep_tpu.obs.registry import MetricsRegistry
from kafkastreams_cep_tpu.ops.engine import EngineConfig
from kafkastreams_cep_tpu.parallel import (
    AdmissionPacer,
    BatchedDeviceNFA,
    CapacityAutosizer,
)
from kafkastreams_cep_tpu.pattern.expressions import value
from kafkastreams_cep_tpu.state.serde import ShapeRestoreError
from kafkastreams_cep_tpu.streams.emission import (
    identity_prefix,
    sequence_ident_frames,
)

from test_gc_groups import (
    assert_trees_equal,
    branching_fold_pattern,
    letter_stream,
)

TS = 1_000_000

#: The arm shape every golden test starts from and returns to.
C0 = dict(lanes=32, nodes=256, matches=128, matches_per_step=32)


def emission_digests(got):
    """blake2b-16 emission-identity digests, the exactly-once currency
    (streams/emission.py): bitwise equality here is the contract the
    resize must not disturb."""
    out = []
    for key in sorted(got):
        for seq in got[key]:
            h = hashlib.blake2b(digest_size=16)
            h.update(identity_prefix("q", key))
            h.update(sequence_ident_frames(seq))
            out.append(h.hexdigest())
    return sorted(out)


def drive_resized(streams, resize_at, *, engine="xla", drain_mode="flat",
                  gc_group=1, T=4, config_kw=C0):
    """Advance T-event batches with deferred decode, draining after every
    batch; `resize_at` maps batch index -> EngineConfig replace kwargs
    applied AFTER that batch's drain. Returns (matches, engine)."""
    keys = list(streams)
    config = EngineConfig(gc_group=gc_group, **config_kw)
    bat = BatchedDeviceNFA(
        compile_pattern(branching_fold_pattern()), keys=keys, config=config,
        engine=engine, drain_mode=drain_mode,
    )
    got = {k: [] for k in keys}
    n = max(len(s) for s in streams.values())
    for b in range(math.ceil(n / T)):
        chunk = {
            k: s[b * T: (b + 1) * T]
            for k, s in streams.items()
            if s[b * T: (b + 1) * T]
        }
        bat.advance_packed(bat.pack(chunk), decode=False)
        for k, seqs in bat.drain().items():
            got[k].extend(seqs)
        if b in resize_at:
            assert bat.resize(replace(bat.config, **resize_at[b]))
    for k, seqs in bat.drain().items():
        got[k].extend(seqs)
    return got, bat


GROW = dict(lanes=64, nodes=512, matches=256, matches_per_step=64)


@pytest.mark.parametrize("engine", ["xla", "pallas_interpret"])
@pytest.mark.parametrize("drain_mode", ["flat", "pool"])
def test_resize_cycle_bitwise_golden(engine, drain_mode):
    """grow -> shrink-back -> grow -> shrink-back across a live stream ==
    never having resized: same matches, same emission digests, and the
    final state + pool trees bitwise (the graft pastes compacted live
    prefixes into init-valued pads, so grow-back is exact)."""
    streams = {f"k{i}": letter_stream(500 + i, 24, f"k{i}") for i in range(2)}
    kw = dict(engine=engine, drain_mode=drain_mode)
    want, straight = drive_resized(streams, {}, **kw)
    got, cycled = drive_resized(
        streams,
        {0: GROW, 2: dict(C0), 3: GROW, 4: dict(C0)},
        **kw,
    )
    assert got == want
    assert emission_digests(got) == emission_digests(want)
    assert cycled.resizes == 4 and straight.resizes == 0
    for c in ("lane_drops", "node_drops", "match_drops"):
        assert cycled.stats[c] == 0 and straight.stats[c] == 0
    assert_trees_equal(straight.state, cycled.state, "state")
    assert_trees_equal(straight.pool, cycled.pool, "pool")


def test_resize_mid_gc_group():
    """A resize landing mid-group (advance index not a multiple of G)
    flushes the group early; matches, digests and final trees must still
    equal the G=1 straight run (cadence never changes WHAT is
    computed)."""
    streams = {f"k{i}": letter_stream(522 + i, 24, f"k{i}") for i in range(2)}
    want, b1 = drive_resized(streams, {}, gc_group=1)
    got, bg = drive_resized(streams, {1: GROW, 3: dict(C0)}, gc_group=4)
    assert got == want
    assert emission_digests(got) == emission_digests(want)
    assert_trees_equal(b1.state, bg.state, "state")
    assert_trees_equal(b1.pool, bg.pool, "pool")


def test_resize_under_armed_event_time_gate():
    """Resize while per-key EventTimeGates hold undelivered reordered
    events: the gated+resized run's matches equal the gated no-resize
    run's (the gate is host state; the resize must not perturb what the
    engine computes from the released stream)."""
    from kafkastreams_cep_tpu.time import EventTimeGate

    keys = [f"k{i}" for i in range(2)]
    streams = {k: letter_stream(526 + i, 24, k) for i, k in enumerate(keys)}
    # Shuffle within a small bound so the gates genuinely reorder.
    rng = random.Random(7)
    shuffled = {}
    for k, s in streams.items():
        evs = list(s)
        for i in range(0, len(evs) - 2, 3):
            window = evs[i:i + 3]
            rng.shuffle(window)
            evs[i:i + 3] = window
        shuffled[k] = evs

    def run(resize_at):
        gates = {
            k: EventTimeGate(
                capacity=64, lateness_ms=8, registry=MetricsRegistry()
            )
            for k in keys
        }  # offer() holds records until the watermark clears them
        bat = BatchedDeviceNFA(
            compile_pattern(branching_fold_pattern()), keys=keys,
            config=EngineConfig(**C0), engine="xla", drain_mode="flat",
        )
        got = {k: [] for k in keys}
        T = 4
        for b in range(math.ceil(24 / T)):
            chunk = {}
            for k in keys:
                batch_evs = shuffled[k][b * T: (b + 1) * T]
                if not batch_evs:
                    continue
                released = [e for e, _clk in gates[k].offer_batch(batch_evs)]
                if released:
                    chunk[k] = released
            if chunk:
                bat.advance_packed(bat.pack(chunk), decode=False)
                for k, seqs in bat.drain().items():
                    got[k].extend(seqs)
            if b in resize_at:
                assert bat.resize(replace(bat.config, **resize_at[b]))
        tail = {k: [e for e, _clk in gates[k].flush()] for k in keys}
        tail = {k: evs for k, evs in tail.items() if evs}
        if tail:
            bat.advance_packed(bat.pack(tail), decode=False)
        for k, seqs in bat.drain().items():
            got[k].extend(seqs)
        return got

    want = run({})
    got = run({1: GROW, 3: dict(C0)})
    assert got == want
    assert emission_digests(got) == emission_digests(want)


def test_shrink_refuses_when_live_state_exceeds_target():
    """Satellite 2: a cross-shape restore that would cut live occupancy
    raises ShapeRestoreError instead of truncating -- here, pending
    undrained matches exceed the target pend ring."""
    keys = ["k0"]
    stream = [
        Event("k0", "ACCCCD"[i % 6], TS + i, "t", 0, i) for i in range(18)
    ]
    bat = BatchedDeviceNFA(
        compile_pattern(branching_fold_pattern()), keys=keys,
        config=EngineConfig(**C0), engine="xla", drain_mode="flat",
    )
    # Deferred decode: the pend ring stays occupied across the advance.
    bat.advance_packed(bat.pack({"k0": stream}), decode=False)
    with pytest.raises(ShapeRestoreError):
        bat.resize(replace(bat.config, matches=2))
    # The refusal left the engine usable at its old shape.
    assert bat.config.matches == C0["matches"]
    assert sum(len(v) for v in bat.drain().values()) > 2


def test_autosizer_counts_refused_shrink():
    """The autosizer treats ShapeRestoreError as "not now": refused
    counter up, no raise, shape unchanged."""
    bat = BatchedDeviceNFA(
        compile_pattern(branching_fold_pattern()), keys=["k0"],
        config=EngineConfig(**C0), engine="xla", drain_mode="flat",
    )
    stream = [
        Event("k0", "ACCCCD"[i % 6], TS + i, "t", 0, i) for i in range(18)
    ]
    bat.advance_packed(bat.pack({"k0": stream}), decode=False)
    auto = CapacityAutosizer(bat)
    auto._apply(dict(lanes=C0["lanes"], nodes=C0["nodes"], matches=2))
    assert auto.refused == 1 and auto.resizes == 0
    assert bat.config.matches == C0["matches"]
    assert auto.state()["refused"] == 1


def test_autosizer_drop_reactive_grow_couples_matches_per_step():
    """A latched match-drop delta doubles `matches` AND
    `matches_per_step` (the counter cannot tell the pend ring from the
    per-(key,step) emission cap apart), and with `t` passed the ring is
    re-grown to keep t * matches_per_step <= matches in the same move."""
    cfg = EngineConfig(lanes=16, nodes=512, matches=8, matches_per_step=2)
    bat = BatchedDeviceNFA(
        compile_pattern(branching_fold_pattern()), keys=["k0"],
        config=cfg, engine="xla", drain_mode="flat",
    )
    # A C C C C C D fans out one_or_more branches: far more than 2
    # emissions in the final step and more than 8 pending -- drops latch
    # at the drain boundary.
    stream = [Event("k0", v, TS + i, "t", 0, i)
              for i, v in enumerate("ACCCCCD" * 2)]
    bat.advance({"k0": stream})
    bat.drain()
    assert bat.stats["match_drops"] > 0
    auto = CapacityAutosizer(bat)
    auto.observe(events=len(stream), t=4)
    assert bat.config.matches_per_step == 4       # doubled
    assert bat.config.matches >= 16               # doubled + t-coupled
    assert bat.config.matches >= 4 * bat.config.matches_per_step
    assert auto.resizes >= 1
    assert auto.state()["matches_per_step"] == 4


def test_autosizer_ensure_page_and_suggest_t():
    """`ensure_page(t)` enforces the loss-free admission requirement
    (t * matches_per_step <= matches, pow2); `suggest_t()` is the
    cadence controller's advisory extent, pow2-quantized -- satellite 1
    wires it in, so it must be live, not dead API."""
    cfg = EngineConfig(lanes=8, nodes=256, matches=16, matches_per_step=4)
    bat = BatchedDeviceNFA(
        compile_pattern(branching_fold_pattern()), keys=["k0"],
        config=cfg, engine="xla", drain_mode="flat",
    )
    auto = CapacityAutosizer(bat)
    auto.ensure_page(16)
    assert bat.config.matches >= 16 * 4
    assert bat.config.matches & (bat.config.matches - 1) == 0  # pow2
    t = auto.suggest_t()
    assert auto.cadence.t_min <= t <= auto.cadence.t_max
    assert t & (t - 1) == 0
    assert auto.state()["suggest_t"] == t


class _FakeEngine:
    """Host-only stand-in for the pure control-law units: carries just
    the surface the autosizer reads (config, metrics, occupancy bound,
    lane_obs, resize)."""

    def __init__(self, cfg):
        self.config = cfg
        self.metrics = MetricsRegistry()
        self.query_name = "fake"
        self.target_emit_ms = None
        self.gc_group = cfg.gc_group
        self.lane_obs = 0
        self.occ = (0, 0, 0)  # (ring occupancy, region fill, pos)

    def _occupancy_bound(self):
        return self.occ

    def resize(self, cfg):
        changed = cfg != self.config
        self.config = cfg
        return changed


def test_autosizer_proactive_grow_respects_budget_and_cooldown():
    eng = _FakeEngine(EngineConfig(lanes=8, nodes=256, matches=64))
    auto = CapacityAutosizer(eng, compile_budget=1, cooldown=1)
    eng.occ = (60, 10, 0)  # ring at 94% of 64: above grow_frac
    auto.observe()
    assert eng.config.matches == 128 and auto.resizes == 1
    # Budget exhausted: the next hot tick must not grow.
    eng.occ = (125, 10, 0)
    auto.observe()
    assert eng.config.matches == 128 and auto.resizes == 1


def test_autosizer_patience_shrink_floors_at_arm_config():
    eng = _FakeEngine(EngineConfig(lanes=8, nodes=256, matches=64))
    auto = CapacityAutosizer(
        eng, compile_budget=8, cooldown=1, shrink_patience=3
    )
    # Grow once so there is something to give back.
    eng.occ = (60, 10, 0)
    auto.observe()
    assert eng.config.matches == 128
    eng.occ = (1, 1, 0)  # cold
    for _ in range(3):
        auto.observe()
    assert eng.config.matches == 64  # halved after patience...
    for _ in range(8):
        auto.observe()
    assert eng.config.matches == 64  # ...but never below the arm shape
    assert eng.config.lanes == 8 and eng.config.nodes == 256


def test_admission_pacer_pow2_pacing():
    pacer = AdmissionPacer(target_poll_ms=100.0, min_batch=32, max_batch=8192)
    assert pacer.suggest_batch() == 32  # no rate signal yet
    pacer._rate_ev_s = 10_000.0  # 100 ms worth = 1000 records -> pow2 1024
    assert pacer.suggest_batch() == 1024
    pacer._rate_ev_s = 10_000_000.0
    assert pacer.suggest_batch() == 8192  # clamped
    st = pacer.state()
    assert set(st) == {"rate_ev_s", "batch", "target_poll_ms"}
    with pytest.raises(ValueError):
        AdmissionPacer(target_poll_ms=0)


def abc_pattern():
    return (
        QueryBuilder()
        .select("a").where(value() == "A")
        .then().select("b").where(value() == "B")
        .then().select("c").where(value() == "C")
        .build()
    )


def _run_topology(runtime, nkeys, **opts):
    from kafkastreams_cep_tpu.streams.builder import ComplexStreamsBuilder
    from kafkastreams_cep_tpu.streams.log import RecordLog

    log = RecordLog()
    b = ComplexStreamsBuilder(log=log, app_id="auto")
    (b.stream("letters")
      .query("q1", abc_pattern(), runtime=runtime, **opts)
      .to("matches"))
    topo = b.build()
    off = 0
    for i in range(nkeys):
        for v in "ABCABCXABC":
            topo.process("letters", f"k{i}", v, timestamp=1000 + off,
                         offset=off)
            off += 1
    topo.flush()
    node = topo.queries[0][1]
    return node, sorted((r.key, r.value) for r in log.read("matches"))


def test_auto_runtime_routes_small_stream_to_host():
    node, out = _run_topology("auto", 4, promote_after=8)
    st = node.processor.state()
    assert st["runtime"] == "host"
    assert node.processor.device is None
    assert len(out) == 4 * 3


def test_auto_runtime_promotes_with_identical_emissions():
    """Crossing the key threshold promotes host -> device; the sink
    records (key, payload) are identical to an all-device run -- the
    promotion replay is digest-deduped, so nothing is double-emitted."""
    cfg = EngineConfig(lanes=16, nodes=512, matches=128)
    node_a, auto_out = _run_topology(
        "auto", 12, promote_after=8, config=cfg
    )
    st = node_a.processor.state()
    assert st["runtime"] == "tpu"
    assert node_a.processor.autosizer is not None  # armed at promotion
    node_t, dev_out = _run_topology("tpu", 12, batch_size=64, config=cfg)
    assert auto_out == dev_out


# ---------------------------------------------------------------- artifacts
sys.path.insert(
    0, os.path.join(os.path.dirname(__file__), os.pardir, "scripts")
)


def test_perf_ledger_excuses_autosize_flag_flip():
    from perf_ledger import autosize_change, compare_artifacts

    assert autosize_change(None, True) and autosize_change(False, True)
    assert not autosize_change(None, None) and not autosize_change(True, True)
    prev = {"configs": {"c": {"eps": 100.0}}, "platform": "cpu",
            "mode": "smoke"}
    cur = {"configs": {"c": {"eps": 50.0}}, "platform": "cpu",
           "mode": "smoke", "autosized": True}
    block = compare_artifacts(prev, cur)
    assert block["regressed"] and block["excused"]
    assert block["excuse"] == "autosize_change"
    assert block["autosized_prev"] is None and block["autosized_cur"] is True
    # Same flag on both sides: a real regression stays unexcused.
    prev2 = dict(prev, autosized=True)
    block2 = compare_artifacts(prev2, cur)
    assert block2["regressed"] and not block2["excused"]
    assert block2["excuse"] is None


def test_bench_schema_validates_autosize_block_both_ways():
    from check_bench_schema import validate as validate_bench_schema

    from test_obs import _valid_artifact

    art = _valid_artifact()
    art["autosized"] = True
    state = {
        "lanes": 64, "nodes": 8192, "matches": 1024,
        "matches_per_step": 16, "suggest_t": 64, "resizes": 2,
        "refused": 0, "ticks": 5, "compile_budget": 6,
        "floor": {"lanes": 64, "nodes": 8192, "matches": 1024},
        "cadence": {
            "target_emit_ms": 500.0, "gc_group": 1, "suggest_t": 64,
            "p99_ms": None, "rate_ev_s": 100.0, "ticks": 5,
            "adjustments": 0, "gc_changes": 0, "compile_budget": 6,
            "compiles_seen": None,
        },
        "compiles_seen": None,
    }
    block = {
        "state": state, "settle_rounds": 3,
        "warmup_drops": {"lane_drops": 0, "node_drops": 0,
                         "match_drops": 12},
    }
    art["autosize"] = block
    art["configs"]["skip_any8_batched"]["autosize"] = {
        "state": dict(state), "settle_rounds": 3,
        "warmup_drops": dict(block["warmup_drops"]),
    }
    assert validate_bench_schema(art) == []
    # Both ways: an undocumented key inside the block is an error, and a
    # state missing its schema discriminator fields is an error.
    bad = _valid_artifact()
    bad["autosize"] = {"state": dict(state), "settle_rounds": 1,
                       "warmup_drops": dict(block["warmup_drops"]),
                       "surprise": 1}
    assert any("surprise" in e for e in validate_bench_schema(bad))
    bad2 = _valid_artifact()
    s2 = dict(state)
    del s2["matches_per_step"]
    bad2["autosize"] = {"state": s2, "settle_rounds": 1,
                        "warmup_drops": dict(block["warmup_drops"])}
    assert any("matches_per_step" in e for e in validate_bench_schema(bad2))
