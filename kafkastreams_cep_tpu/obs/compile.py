"""Compile-cost telemetry: observe XLA compiles per (function, signature).

ROADMAP item 3's compile cache and every retrace-sensitive path (the
flatten fns compile per pow2 bucket, add_keys retraces per key extent)
share one blind spot: nothing counted compiles or their cost, so a
recompile storm looked like generic slowness. The `CompileWatch` shim
wraps a jitted callable and watches its *shape signatures*: the first
call under a new signature is exactly when XLA traces + compiles, so its
wall is recorded as the compile observation for that (function,
signature) pair, and `Lowered.cost_analysis()` contributes FLOPs/bytes
estimates when the backend provides them.

Registry series (PERF.md v13):

- ``cep_compiles_total{fn}``        new-signature observations (compiles)
- ``cep_compile_seconds{fn}``       first-call wall per compile (histogram;
                                    trace + XLA compile + first dispatch --
                                    an upper bound on pure compile)
- ``cep_compile_flops{fn}``         latest cost_analysis() FLOPs estimate
- ``cep_compile_bytes{fn}``         latest cost_analysis() bytes-accessed

Hot-path contract: a warm call (signature already seen) pays one
host-side signature probe -- tree_flatten over the arg pytree plus a
LOCK-FREE dict membership test on shape/dtype metadata (dict reads are
GIL-atomic; the lock guards only the miss path); no device sync, no
retrace -- so the zero-sync advance pin holds with the shim armed
(tests/test_obs.py). The cost_analysis lowering runs once per new
signature and is best-effort: any failure (pallas lowerings, backends
without cost models) degrades to None, never an exception on the data
path.
"""
from __future__ import annotations

import itertools
import threading
import time
from typing import Any, Callable, Dict, Optional, Tuple

from .registry import MetricsRegistry, default_registry

__all__ = ["CompileWatch", "shape_signature"]

#: Compile-wall-flavored buckets (seconds): CPU smoke compiles land
#: ~10-100 ms, flagship TPU plane compiles run to minutes.
COMPILE_BUCKETS = (
    0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 15.0, 60.0, 300.0,
)


def shape_signature(args: Tuple[Any, ...]) -> Tuple[Any, ...]:
    """Hashable (treedef, leaf shape/dtype) signature of a call's args --
    the same information jit keys its cache on, read from host-side
    metadata only (never the device)."""
    import jax

    leaves, treedef = jax.tree_util.tree_flatten(args)
    # dtype objects (np.dtype) are hashable -- no per-call string
    # construction on the warm path; non-array leaves key on their type.
    return (
        treedef,
        tuple(
            (getattr(l, "shape", None), getattr(l, "dtype", None) or type(l))
            for l in leaves
        ),
    )


class CompileWatch:
    """Wrap jitted entry points; record compile count/wall/cost per
    (function label, shape signature) into `registry`.

    One watch per engine instance (it rides the engine's registry); the
    `seen` map is guarded by a lock because drain-side fns run on the
    decode worker while the advance path runs on the caller's thread.
    """

    def __init__(
        self,
        registry: Optional[MetricsRegistry] = None,
        estimate_cost: bool = True,
    ) -> None:
        self.registry = registry if registry is not None else default_registry()
        self.estimate_cost = estimate_cost
        self._seen: Dict[Tuple[str, int, Any], bool] = {}
        self._lock = threading.Lock()
        self._wrap_ids = itertools.count()
        r = self.registry
        self._m_compiles = r.counter(
            "cep_compiles_total",
            "New-shape-signature observations (XLA compiles) per entry point",
            labels=("fn",),
        )
        self._m_seconds = r.histogram(
            "cep_compile_seconds",
            "First-call wall per compile (trace + compile + first dispatch)",
            labels=("fn",),
            buckets=COMPILE_BUCKETS,
        )
        self._m_flops = r.gauge(
            "cep_compile_flops",
            "cost_analysis() FLOPs estimate of the latest compile",
            labels=("fn",),
        )
        self._m_bytes = r.gauge(
            "cep_compile_bytes",
            "cost_analysis() bytes-accessed estimate of the latest compile",
            labels=("fn",),
        )

    # ------------------------------------------------------------------ API
    def compiles(self, fn: str) -> int:
        """Observed compiles for one label (test/introspection helper)."""
        return int(self._m_compiles.labels(fn=fn).value)

    @property
    def seen_count(self) -> int:
        """Distinct (program, signature) pairs observed so far -- a cheap
        'did anything compile since I last looked' probe (len() is
        GIL-atomic; the engine's sampled phase profiling uses it to keep
        compile walls out of the compute histograms)."""
        return len(self._seen)

    def wrap(self, fn: Callable, name: str) -> Callable:
        """The instrumented callable: pass-through semantics, compile
        observations on new shape signatures.

        The seen-key carries a per-wrap token alongside the label: two
        DISTINCT programs under one label (the per-(Mb, Cb) flatten
        buckets; a rebuilt advance after the pallas fallback) are
        separate compiles even when their arg shapes coincide -- bucket
        churn is exactly the recompile storm this watch must show."""
        token = next(self._wrap_ids)

        def wrapped(*args: Any) -> Any:
            try:
                sig = (name, token, shape_signature(args))
            except Exception:
                return fn(*args)  # unhashable arg tree: observe nothing
            # Lock-free warm path: dict membership is GIL-atomic, and a
            # stale miss only routes through the locked miss path below.
            if sig in self._seen:
                return fn(*args)
            t0 = time.perf_counter()
            out = fn(*args)
            dt = time.perf_counter() - t0
            with self._lock:
                first = sig not in self._seen
                self._seen[sig] = True
            if first:
                self._m_compiles.labels(fn=name).inc()
                self._m_seconds.labels(fn=name).observe(dt)
                self._estimate(fn, name, args)
            return out

        wrapped.__name__ = f"compile_watch[{name}]"
        wrapped.__wrapped__ = fn
        return wrapped

    def _estimate(self, fn: Callable, name: str, args: Tuple[Any, ...]) -> None:
        """Best-effort cost_analysis() on the already-compiled signature:
        the jit cache is warm, so .lower() re-traces but never re-compiles
        XLA; failures (no .lower, pallas, backend without a cost model)
        leave the gauges untouched."""
        if not self.estimate_cost:
            return
        lower = getattr(fn, "lower", None)
        if lower is None:
            return
        try:
            cost = lower(*args).cost_analysis()
            if isinstance(cost, (list, tuple)):  # per-device variants
                cost = cost[0] if cost else None
            if not cost:
                return
            flops = cost.get("flops")
            if flops is not None:
                self._m_flops.labels(fn=name).set(float(flops))
            nbytes = cost.get("bytes accessed")
            if nbytes is not None:
                self._m_bytes.labels(fn=name).set(float(nbytes))
        except Exception:
            pass
