"""Pattern -> NFA compiler.

Implements the SASE NFA^b construction rules of the reference compiler
(reference: core/.../cep/pattern/StagesFactory.java:49-191):

  * walk the ancestor chain newest -> oldest, prepending a `$final` stage;
  * cardinality ONE -> BEGIN edge, ONE_OR_MORE -> TAKE edge;
  * skip-till-any  -> IGNORE edge with a True predicate;
    skip-till-next -> IGNORE edge with !take;
  * TAKE stages get a PROCEED edge: succ OR !take (strict contiguity) /
    succ OR (!take AND !ignore) (skip strategies);
  * times(n) / one_or_more expand into chained internal BEGIN stages;
  * optional stages get a SKIP_PROCEED edge: succ AND !take;
  * per-stage topic filters are ANDed into predicates;
  * the window is pushed onto every stage.

Raises InvalidPatternException for a final one_or_more/optional stage.
"""
from __future__ import annotations

from typing import List, Optional

from .matcher import Predicate, TopicPredicate, TruePredicate, and_, not_, or_
from .pattern import Cardinality, Pattern, Strategy
from .stages import Edge, EdgeOperation, Stage, Stages, StateType


class InvalidPatternException(Exception):
    pass


def compile_pattern(pattern: Pattern) -> Stages:
    """Compile a Pattern chain into the NFA stage graph."""
    if pattern is None:
        raise ValueError("Cannot compile a null pattern")

    compiler = _Compiler()
    return compiler.compile(pattern)


def ensure_stages(pattern_or_stages) -> Stages:
    """Accept either a Pattern (compiled here, exactly once per call site)
    or an already-compiled Stages -- the normalization every deployment
    entry point shares."""
    if isinstance(pattern_or_stages, Pattern):
        return compile_pattern(pattern_or_stages)
    return pattern_or_stages


class _Compiler:
    def __init__(self) -> None:
        self._next_id = 0

    def _new_id(self) -> int:
        stage_id = self._next_id
        self._next_id += 1
        return stage_id

    def compile(self, pattern: Pattern) -> Stages:
        sequence: List[Stage] = []

        successor_stage = Stage(self._new_id(), "$final", StateType.FINAL)
        sequence.append(successor_stage)

        successor_pattern: Optional[Pattern] = None
        current = pattern
        while current.ancestor is not None:
            stages = self._build_stages(StateType.NORMAL, current, successor_stage, successor_pattern)
            sequence.extend(stages)
            successor_stage = stages[-1]
            successor_pattern = current
            current = current.ancestor
        sequence.extend(self._build_stages(StateType.BEGIN, current, successor_stage, successor_pattern))

        return Stages(sequence)

    def _build_stages(
        self,
        state_type: StateType,
        current: Pattern,
        successor_stage: Stage,
        successor_pattern: Optional[Pattern],
    ) -> List[Stage]:
        cardinality = current.cardinality
        has_mandatory_state = cardinality == Cardinality.ONE_OR_MORE
        current_type = StateType.NORMAL if has_mandatory_state else state_type

        stage = Stage(self._new_id(), current.name, current_type)
        window_ms = self._window_ms(current, successor_pattern)
        stage.window_ms = window_ms
        stage.aggregates = list(current.aggregates)

        selected = current.selected
        # Selected.from_topic leaves the strategy unset; normalize to strict
        # contiguity (the reference would NPE on this input).
        strategy = selected.strategy if selected.strategy is not None else Strategy.STRICT_CONTIGUITY
        predicate: Predicate = current.predicate if current.predicate is not None else TruePredicate()
        if selected.topic is not None:
            predicate = and_(TopicPredicate(selected.topic), predicate)

        operation = EdgeOperation.BEGIN if cardinality == Cardinality.ONE else EdgeOperation.TAKE
        stage.add_edge(Edge(operation, predicate, successor_stage))

        ignore: Optional[Predicate] = None
        if strategy == Strategy.SKIP_TIL_ANY_MATCH:
            ignore = TruePredicate()
            stage.add_edge(Edge(EdgeOperation.IGNORE, ignore, None))
        elif strategy == Strategy.SKIP_TIL_NEXT_MATCH:
            ignore = not_(predicate)
            stage.add_edge(Edge(EdgeOperation.IGNORE, ignore, None))

        if operation == EdgeOperation.TAKE:
            if successor_pattern is None and successor_stage.is_final:
                raise InvalidPatternException(
                    "Cannot define a pattern with a final stage expecting multiple matching events"
                )
            successor_predicate: Predicate = (
                successor_pattern.predicate
                if successor_pattern.predicate is not None
                else TruePredicate()
            )
            if successor_pattern.selected.topic is not None:
                successor_predicate = and_(
                    TopicPredicate(successor_pattern.selected.topic), successor_predicate
                )
            if strategy == Strategy.STRICT_CONTIGUITY:
                proceed = or_(successor_predicate, not_(predicate))
            else:
                proceed = or_(successor_predicate, and_(not_(predicate), not_(ignore)))
            stage.add_edge(Edge(EdgeOperation.PROCEED, proceed, successor_stage))

        stages = [stage]

        times = current.times
        if has_mandatory_state or times > 1:
            while True:
                internal = Stage(self._new_id(), current.name, state_type)
                internal.add_edge(Edge(EdgeOperation.BEGIN, predicate, stage))
                if ignore is not None:
                    internal.add_edge(Edge(EdgeOperation.IGNORE, ignore, None))
                internal.window_ms = window_ms
                internal.aggregates = list(current.aggregates)
                stages.append(internal)
                stage = internal
                times -= 1
                if times <= 1:
                    break

        if current.is_optional:
            if successor_pattern is None and successor_stage.is_final:
                raise InvalidPatternException("Cannot define a pattern with an optional final stage")
            successor_predicate = (
                successor_pattern.predicate
                if successor_pattern.predicate is not None
                else TruePredicate()
            )
            skip = and_(successor_predicate, not_(predicate))
            stage.add_edge(Edge(EdgeOperation.SKIP_PROCEED, skip, successor_stage))

        return stages

    @staticmethod
    def _window_ms(current: Pattern, successor_pattern: Optional[Pattern]) -> int:
        if current.window_ms is not None:
            return current.window_ms
        if successor_pattern is not None and successor_pattern.window_ms is not None:
            return successor_pattern.window_ms
        return -1
