"""Instrumented-lock runtime monitor: lock-order cycle detection.

The static thread checker (analysis/threads.py) proves shared writes are
*locked*; it cannot prove two locks are always taken in the same order.
This monitor can: while armed, ``threading.Lock``/``threading.RLock``
return instrumented wrappers that record, per thread, which lock sites
were held when each lock site was acquired. Every (held -> acquired)
pair is an edge in the lock-order graph; a cycle in that graph is a
potential deadlock (two threads can interleave the cyclic orders), even
if the run never actually deadlocked.

Lock identity is the *creation site* (file:line), not the instance:
per-request or per-engine lock instances from one source line are one
ordering class, so the graph is stable across runs and its nodes are
attributable (which is also why anonymous thread roots are a lint
finding -- CEP-T03 -- the edge samples record thread names).

Armed in the chaos (`-m chaos`) and quick-soak (`-m soak`) suites via a
tests/conftest.py fixture: those are the runs that exercise the obs
serve/clock/scraper/decode threads together. Overhead while armed is
one dict update per acquire; disarmed, nothing is patched.

Usage::

    with lock_monitor() as mon:
        ... multithreaded work ...
    assert mon.cycles() == []
"""
from __future__ import annotations

import sys
import threading
import _thread
from contextlib import contextmanager
from typing import Dict, Iterator, List, Optional, Set, Tuple

__all__ = ["LockMonitor", "lock_monitor", "active_monitor"]

#: the un-instrumented allocator (graph bookkeeping must not recurse
#: into the instrumented constructors).
_raw_lock = _thread.allocate_lock

_active: Optional["LockMonitor"] = None


def active_monitor() -> Optional["LockMonitor"]:
    return _active


def _creation_site(depth: int = 2) -> str:
    """file:line of the instrumented constructor's caller, with stdlib
    frames skipped (a Condition() allocating its RLock should attribute
    to the caller of Condition, not to threading.py)."""
    frame = sys._getframe(depth)
    while frame is not None:
        fname = frame.f_code.co_filename
        if "threading" not in fname.rsplit("/", 1)[-1]:
            break
        frame = frame.f_back
    if frame is None:  # pragma: no cover - stdlib-only stack
        return "<unknown>"
    return f"{frame.f_code.co_filename}:{frame.f_lineno}"


class _InstrumentedLock:
    """Wraps a real lock; delegates everything, records ordering edges."""

    def __init__(self, monitor: "LockMonitor", inner, site: str) -> None:
        self._mon = monitor
        self._inner = inner
        self._site = site

    # ------------------------------------------------------------- protocol
    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        got = self._inner.acquire(blocking, timeout)
        if got:
            self._mon._record_acquire(self._site)
        return got

    def release(self) -> None:
        self._inner.release()
        self._mon._record_release(self._site)

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> bool:
        self.release()
        return False

    def locked(self) -> bool:
        return self._inner.locked()

    def __getattr__(self, name: str):
        # Condition and friends poke at private lock internals
        # (_is_owned, _release_save, _at_fork_reinit, ...).
        return getattr(self._inner, name)


class LockMonitor:
    """The lock-order graph and the Lock/RLock patch points."""

    def __init__(self, max_edges: int = 4096) -> None:
        self.max_edges = max_edges
        self._graph_lock = _raw_lock()
        #: (held site, acquired site) -> sample {thread name}
        self.edges: Dict[Tuple[str, str], Set[str]] = {}
        self.acquires = 0
        self._held = threading.local()
        self._installed = False
        self._orig_lock = None
        self._orig_rlock = None

    # ------------------------------------------------------------ recording
    def _stack(self) -> List[str]:
        stack = getattr(self._held, "stack", None)
        if stack is None:
            stack = self._held.stack = []
        return stack

    def _record_acquire(self, site: str) -> None:
        if not self._installed:
            return  # wrapper outlived the monitor: plain lock behavior
        # The counter is deliberately unlocked: a lost increment is fine
        # for a diagnostic count, and taking the graph lock on EVERY
        # acquire would serialize all monitored threads through one
        # point (the monitor must not create the contention it audits).
        self.acquires += 1
        stack = self._stack()
        if stack:
            tname = threading.current_thread().name
            with self._graph_lock:
                for held in stack:
                    if held == site:
                        continue  # re-entrant same-site acquire
                    edge = (held, site)
                    samples = self.edges.get(edge)
                    if samples is None:
                        if len(self.edges) >= self.max_edges:
                            continue
                        samples = self.edges[edge] = set()
                    if len(samples) < 8:
                        samples.add(tname)
        stack.append(site)

    def _record_release(self, site: str) -> None:
        stack = self._stack()
        # Release order need not be LIFO; drop the innermost match.
        for i in range(len(stack) - 1, -1, -1):
            if stack[i] == site:
                del stack[i]
                break

    # --------------------------------------------------------------- verdict
    def cycles(self) -> List[List[str]]:
        """Elementary cycles in the lock-order graph (site lists); empty
        means no potential lock-order deadlock was observed."""
        with self._graph_lock:
            adj: Dict[str, Set[str]] = {}
            for a, b in self.edges:
                adj.setdefault(a, set()).add(b)
        out: List[List[str]] = []
        seen_cycles: Set[Tuple[str, ...]] = set()
        # Iterative DFS per start node; path-based cycle extraction. The
        # graph is tiny (lock *sites*, not instances), so simple wins.
        for start in sorted(adj):
            stack: List[Tuple[str, Iterator[str]]] = [
                (start, iter(sorted(adj.get(start, ()))))
            ]
            on_path = [start]
            while stack:
                node, it = stack[-1]
                advanced = False
                for nxt in it:
                    if nxt == start:
                        cyc = on_path[:]
                        key = tuple(sorted(cyc))
                        if key not in seen_cycles:
                            seen_cycles.add(key)
                            out.append(cyc + [start])
                    elif nxt not in on_path and nxt in adj:
                        stack.append(
                            (nxt, iter(sorted(adj.get(nxt, ()))))
                        )
                        on_path.append(nxt)
                        advanced = True
                        break
                if not advanced:
                    stack.pop()
                    on_path.pop()
        return out

    def report(self) -> str:
        lines = [
            f"lockmon: {self.acquires} acquires, "
            f"{len(self.edges)} ordering edge(s)"
        ]
        for (a, b), threads in sorted(self.edges.items()):
            lines.append(f"  {a} -> {b}  [{', '.join(sorted(threads))}]")
        for cyc in self.cycles():
            lines.append("  CYCLE: " + " -> ".join(cyc))
        return "\n".join(lines)

    # ------------------------------------------------------------- patching
    def install(self) -> "LockMonitor":
        global _active
        if self._installed:
            return self
        if _active is not None:
            raise RuntimeError("another LockMonitor is already installed")
        self._orig_lock = threading.Lock
        self._orig_rlock = threading.RLock
        mon = self

        def make_lock():  # noqa: ANN202 - threading.Lock signature
            return _InstrumentedLock(mon, _raw_lock(), _creation_site())

        def make_rlock():
            return _InstrumentedLock(
                mon, mon._orig_rlock(), _creation_site()
            )

        threading.Lock = make_lock  # type: ignore[assignment]
        threading.RLock = make_rlock  # type: ignore[assignment]
        self._installed = True
        _active = self
        return self

    def uninstall(self) -> None:
        global _active
        if not self._installed:
            return
        threading.Lock = self._orig_lock  # type: ignore[assignment]
        threading.RLock = self._orig_rlock  # type: ignore[assignment]
        self._installed = False
        if _active is self:
            _active = None
        # Wrappers created while armed keep working (they own real
        # locks); they just stop growing the graph once uninstalled.


@contextmanager
def lock_monitor():
    """Arm a LockMonitor for the block; yields it (query cycles() after)."""
    mon = LockMonitor().install()
    try:
        yield mon
    finally:
        mon.uninstall()
