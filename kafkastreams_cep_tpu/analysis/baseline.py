"""Baseline semantics: the committed ledger of accepted findings.

``ceplint.baseline.json`` at the repo root is the one escape hatch that
is not a source pragma (doc-side findings have no comment channel, and
bulk-adopting the linter on a brownfield tree needs a ratchet). The
contract keeps it honest:

- every entry must carry a nonempty ``note`` (CEP-B02 otherwise) --
  like pragmas, a baseline without a why is not an audit;
- an entry whose fingerprint matches no current finding is *stale*
  (CEP-B01): the finding was fixed, so the entry must go -- baselines
  only ever shrink by hand or via ``--update-baseline``;
- fingerprints are line-number-free (analysis/core.Finding), so pure
  movement does not churn the file.

``apply_baseline`` marks matched findings ``baselined`` (excluded from
the exit code); ``update`` rewrites the file to exactly the current
unsuppressed findings, preserving notes of surviving entries and
stamping new ones ``TODO: annotate``(which CEP-B02 then flags -- adding
to the baseline is two steps by design: record, then justify).
"""
from __future__ import annotations

import json
import os
from typing import Any, Dict, List, Optional, Set, Tuple

from .core import Finding

BASELINE_NAME = "ceplint.baseline.json"
_TODO = "TODO: annotate"


def default_path(root_dir: str) -> str:
    return os.path.join(root_dir, BASELINE_NAME)


def load(path: str) -> List[Dict[str, Any]]:
    if not os.path.exists(path):
        return []
    with open(path, "r", encoding="utf-8") as f:
        doc = json.load(f)
    entries = doc.get("findings", [])
    if not isinstance(entries, list):
        raise ValueError(f"{path}: 'findings' must be a list")
    return entries


def save(path: str, entries: List[Dict[str, Any]]) -> None:
    doc = {
        "version": 1,
        "tool": "ceplint",
        "findings": sorted(
            entries, key=lambda e: (e.get("path", ""), e.get("code", ""))
        ),
    }
    with open(path, "w", encoding="utf-8") as f:
        json.dump(doc, f, indent=2, sort_keys=True)
        f.write("\n")


def entry_in_scope(
    entry: Dict[str, Any],
    scanned_paths: Optional[Set[str]] = None,
    checkers: Optional[Set[str]] = None,
) -> bool:
    """Could this run have re-observed the entry's finding? False when
    the entry's checker did not run or its file was not scanned."""
    if checkers is not None and entry.get("checker") not in checkers:
        return False
    if (
        scanned_paths is not None
        and entry.get("path") not in scanned_paths
    ):
        return False
    return True


def apply_baseline(
    findings: List[Finding],
    entries: List[Dict[str, Any]],
    scanned_paths: Optional[Set[str]] = None,
    checkers: Optional[Set[str]] = None,
) -> Tuple[List[Finding], List[Finding]]:
    """Mark baselined findings; return (stale-entry, unannotated-entry)
    findings for entries that no longer match / carry no note. Entries
    outside the run's scope (see `entry_in_scope`) are never stale: a
    partial run could not have re-observed them."""
    by_fp: Dict[str, Finding] = {}
    for f in findings:
        if f.suppressed_by is None:
            by_fp.setdefault(f.fingerprint(), f)
    extra: List[Finding] = []
    for entry in entries:
        fp = str(entry.get("fingerprint", ""))
        matched = by_fp.get(fp)
        if matched is None and not entry_in_scope(
            entry, scanned_paths, checkers
        ):
            continue
        if matched is not None:
            matched.baselined = True
            note = str(entry.get("note", "") or "")
            if not note.strip() or note.strip() == _TODO:
                extra.append(
                    Finding(
                        "baseline", "CEP-B02", BASELINE_NAME, 0,
                        f"baseline entry {fp} ({entry.get('code')}, "
                        f"{entry.get('path')}) has no note -- justify it "
                        "or fix the finding",
                        context=f"unannotated:{fp}",
                    )
                )
        else:
            extra.append(
                Finding(
                    "baseline", "CEP-B01", BASELINE_NAME, 0,
                    f"stale baseline entry {fp} ({entry.get('code')}, "
                    f"{entry.get('path')}): no current finding matches -- "
                    "remove it (or run --update-baseline)",
                    context=f"stale:{fp}",
                )
            )
    return [f for f in extra if f.code == "CEP-B01"], [
        f for f in extra if f.code == "CEP-B02"
    ]


def update(
    path: str,
    findings: List[Finding],
    entries: List[Dict[str, Any]],
    scanned_paths: Optional[Set[str]] = None,
    checkers: Optional[Set[str]] = None,
) -> List[Dict[str, Any]]:
    """Rewrite the baseline to the current unsuppressed findings,
    keeping notes of surviving entries (expire semantics: anything not
    re-observed drops out).

    `scanned_paths`/`checkers` bound the rewrite to the run's scope: an
    entry whose checker did not run, or whose file was not scanned, was
    never re-observable -- a partial run (`ceplint one/file.py` or
    `--checker zerosync`) must not silently erase unrelated entries and
    their human-written notes."""
    notes = {
        str(e.get("fingerprint", "")): str(e.get("note", "") or "")
        for e in entries
    }
    out: List[Dict[str, Any]] = []
    seen_fps: set = set()
    for e in entries:
        if not entry_in_scope(e, scanned_paths, checkers):
            out.append(dict(e))
            seen_fps.add(str(e.get("fingerprint", "")))
    for f in findings:
        if f.suppressed_by is not None or f.checker == "baseline":
            continue
        fp = f.fingerprint()
        if fp in seen_fps:
            continue
        out.append(
            {
                "fingerprint": fp,
                "checker": f.checker,
                "code": f.code,
                "path": f.path,
                "message": f.message,
                "note": notes.get(fp, "") or _TODO,
            }
        )
    save(path, out)
    return out
