"""Fold aggregates attached to pattern stages.

Re-design of the reference fold machinery
(reference: core/.../cep/pattern/Aggregator.java:27, StateAggregator.java:26-41).
A fold updates a named per-run register each time the stage consumes an
event. Two forms are supported:

  * expression folds (``Expr`` over event fields + the current register via
    ``agg(name)``) -- run on host *and* device;
  * callable folds ``fn(key, value, current) -> new`` -- host-only, exact
    parity with the reference's Aggregator functional interface.
"""
from __future__ import annotations

from typing import Any, Callable, Optional, Union

from .expressions import Expr


class StateAggregator:
    """A named fold: register name + update function/expression."""

    __slots__ = ("name", "fn", "expression", "initial")

    def __init__(
        self,
        name: str,
        update: Union[Expr, Callable[[Any, Any, Any], Any]],
        initial: Optional[Any] = None,
    ) -> None:
        self.name = name
        self.initial = initial
        if isinstance(update, Expr):
            self.expression: Optional[Expr] = update
            self.fn: Optional[Callable] = None
        else:
            self.expression = None
            self.fn = update

    @property
    def device_compilable(self) -> bool:
        return self.expression is not None

    def apply(self, key: Any, value: Any, current: Any, env_factory=None) -> Any:
        """Host-path register update for one consumed event."""
        if self.fn is not None:
            return self.fn(key, value, current)
        assert self.expression is not None
        env = env_factory(current)
        return self.expression.evaluate(env)

    def __repr__(self) -> str:
        body = self.expression if self.expression is not None else self.fn
        return f"StateAggregator({self.name!r}, {body!r})"
