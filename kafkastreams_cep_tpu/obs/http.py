"""Live introspection plane: stdlib HTTP exposition on a daemon thread.

A production engine must be curl-able mid-stream (ISSUE 7): the
`IntrospectionServer` binds `http.server.ThreadingHTTPServer` on a daemon
thread and serves, with zero third-party dependencies:

- ``/metrics``   Prometheus 0.0.4 text of the attached registry
- ``/snapshot``  the registry's JSON snapshot (the bench `metrics` format)
- ``/healthz``   liveness JSON: server uptime plus whatever the attached
                 `health_fn` reports (LogDriver: poll/commit ages,
                 restore state, fault-arm state)
- ``/tracez``    recent SpanTracer spans as JSON (newest first);
                 ``?kind=match`` serves sampled match-provenance
                 exemplars instead; ``?limit=N`` bounds either;
                 ``?format=chrome`` renders spans AND exemplars as one
                 Chrome-trace/Perfetto document (obs/trace_export.py)
- ``/explainz``  recent emitted-match lineage (ISSUE 20): contributing
                 event identities, run version path, trace-id exemplar,
                 source broker, observed latency -- the read-only "why
                 did this match fire" surface (`explain_fn`, e.g.
                 LogDriver.explain); ``?limit=N`` / ``?query=name``
                 bound and filter
- ``/profilez``  ``?secs=N`` arms an on-demand device xplane capture
                 (ops.profiling.device_trace) for N seconds on a
                 background thread against the running pipeline; the
                 reply returns immediately with the capture's log_dir.
                 One capture at a time; a degraded profiler (no TPU /
                 missing plugin) no-ops with a persistent warning gauge

The server also owns the plane's **clock thread**: callables registered
via `tick_fns` run every `tick_every_s` seconds regardless of stream
traffic. `LogDriver.serve_http` registers its periodic reporter here,
fixing the poll-gated cadence (an idle topic previously never reported --
no poll, no report).

Reads only: every handler renders from host-side registries/rings, so a
scrape can never sync the device or touch the data path.
"""
from __future__ import annotations

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Dict, Iterable, List, Optional
from urllib.parse import parse_qs, urlsplit

from .registry import MetricsRegistry, default_registry
from .trace import SpanTracer

__all__ = ["IntrospectionServer"]


class _Handler(BaseHTTPRequestHandler):
    # Scrapes must never block each other on a slow client.
    protocol_version = "HTTP/1.1"

    def log_message(self, *args: Any) -> None:  # silence per-request noise
        pass

    def do_GET(self) -> None:  # noqa: N802 (http.server naming)
        plane: "IntrospectionServer" = self.server.plane  # type: ignore[attr-defined]
        parts = urlsplit(self.path)
        query = parse_qs(parts.query)
        try:
            route = plane._routes.get(parts.path)
            if route is None:
                self._reply(404, "text/plain; charset=utf-8",
                            f"unknown route {parts.path!r}\n".encode())
                return
            content_type, body = route(query)
        except Exception as exc:  # a broken health_fn must not kill serving
            self._reply(500, "text/plain; charset=utf-8",
                        f"introspection handler failed: {exc}\n".encode())
            return
        self._reply(200, content_type, body)

    def _reply(self, code: int, content_type: str, body: bytes) -> None:
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)


def _limit(query: Dict[str, List[str]], default: int = 64) -> int:
    try:
        return max(0, int(query.get("limit", [default])[0]))
    except (TypeError, ValueError):
        return default


class IntrospectionServer:
    """The live plane: HTTP exposition + the time-driven tick clock.

    `registry`: the exposition source (process default when omitted).
    `tracer`: span source for /tracez (one is created over `registry`
    when omitted, so attaching a server always yields a working /tracez).
    `health_fn`: extra /healthz fields (dict); exceptions surface as 500.
    `match_exemplars`: callable(limit) -> list of provenance dicts for
    /tracez?kind=match (e.g. BatchedDeviceNFA.provenance_exemplars).
    `tick_fns`: called from the clock thread every `tick_every_s` --
    idle-stream periodic reporting lives here, not on the poll path.
    `profile_dir`: where /profilez drops xplane captures (a fresh temp
    dir per capture under the system tmp dir when omitted).
    """

    #: /profilez duration clamp: a runaway ?secs= must not pin the
    #: profiler (and its buffer memory) for hours.
    PROFILE_MAX_SECS = 60.0

    def __init__(
        self,
        registry: Optional[MetricsRegistry] = None,
        tracer: Optional[SpanTracer] = None,
        health_fn: Optional[Callable[[], Dict[str, Any]]] = None,
        match_exemplars: Optional[Callable[[int], List[Dict[str, Any]]]] = None,
        explain_fn: Optional[Callable[[int], List[Dict[str, Any]]]] = None,
        tick_fns: Iterable[Callable[[], Any]] = (),
        tick_every_s: float = 0.25,
        host: str = "127.0.0.1",
        port: int = 0,
        profile_dir: Optional[str] = None,
    ) -> None:
        self.registry = registry if registry is not None else default_registry()
        self.tracer = tracer if tracer is not None else SpanTracer(self.registry)
        self.health_fn = health_fn
        self.match_exemplars = match_exemplars
        self.explain_fn = explain_fn
        self.tick_fns = list(tick_fns)
        self.tick_every_s = max(0.01, float(tick_every_s))
        self._host = host
        self._port = port
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._serve_thread: Optional[threading.Thread] = None
        self._clock_thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._t_start = time.time()
        self.requests = 0
        self.profile_dir = profile_dir
        self._profile_thread: Optional[threading.Thread] = None
        self._profile_lock = threading.Lock()
        # Request counter lock: routes run on per-request handler threads
        # (ThreadingHTTPServer), so the += below would lose updates.
        self._req_lock = threading.Lock()
        self.profile_captures = 0
        self._routes: Dict[str, Callable] = {
            "/metrics": self._route_metrics,
            "/snapshot": self._route_snapshot,
            "/healthz": self._route_healthz,
            "/tracez": self._route_tracez,
            "/explainz": self._route_explainz,
            "/profilez": self._route_profilez,
        }

    # ------------------------------------------------------------- lifecycle
    def start(self) -> "IntrospectionServer":
        if self._httpd is not None:
            return self
        # A restarted server must tick again: stop() leaves the event set,
        # and a set event would kill the fresh clock thread on its first
        # wait() -- silently, since HTTP keeps answering.
        self._stop.clear()
        self._t_start = time.time()
        self._httpd = ThreadingHTTPServer((self._host, self._port), _Handler)
        self._httpd.daemon_threads = True
        self._httpd.plane = self  # type: ignore[attr-defined]
        self._serve_thread = threading.Thread(
            target=self._httpd.serve_forever,
            kwargs={"poll_interval": 0.1},
            name="kct-introspect-http",
            daemon=True,
        )
        self._serve_thread.start()
        if self.tick_fns:
            self._clock_thread = threading.Thread(
                target=self._clock, name="kct-introspect-clock", daemon=True
            )
            self._clock_thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        if self._serve_thread is not None:
            self._serve_thread.join(timeout=5)
            self._serve_thread = None
        if self._clock_thread is not None:
            self._clock_thread.join(timeout=5)
            self._clock_thread = None
        # Read under the profile lock: an in-flight /profilez handler
        # (handler threads are not joined by httpd.shutdown) may be
        # arming a capture concurrently -- the lock orders us after its
        # spawn, and the handler's own stopped-check (below) orders any
        # LATER arm after our _stop.set(). Either way no capture thread
        # survives stop().
        with self._profile_lock:
            profile_thread, self._profile_thread = self._profile_thread, None
        if profile_thread is not None:
            # _stop is set above, so an armed capture's wait() returns
            # immediately and the profiler context closes before teardown.
            profile_thread.join(timeout=5)

    def __enter__(self) -> "IntrospectionServer":
        return self.start()

    def __exit__(self, *exc: Any) -> None:
        self.stop()

    @property
    def port(self) -> int:
        if self._httpd is None:
            raise RuntimeError("server not started")
        return self._httpd.server_address[1]

    @property
    def url(self) -> str:
        return f"http://{self._host}:{self.port}"

    # ----------------------------------------------------------- clock thread
    def _clock(self) -> None:
        """The plane's cadence: tick_fns run on wall time, never on the
        poll path -- an idle stream still reports (ISSUE 7 satellite)."""
        while not self._stop.wait(self.tick_every_s):
            for fn in self.tick_fns:
                try:
                    fn()
                except Exception:
                    import logging

                    logging.getLogger("kafkastreams_cep_tpu.obs").warning(
                        "introspection tick failed", exc_info=True
                    )

    def _count_request(self) -> None:
        with self._req_lock:
            self.requests += 1

    # ---------------------------------------------------------------- routes
    def _route_metrics(self, query: Dict[str, List[str]]):
        self._count_request()
        return (
            "text/plain; version=0.0.4; charset=utf-8",
            self.registry.to_prom_text().encode("utf-8"),
        )

    def _route_snapshot(self, query: Dict[str, List[str]]):
        self._count_request()
        return (
            "application/json",
            json.dumps(self.registry.snapshot()).encode("utf-8"),
        )

    def _route_healthz(self, query: Dict[str, List[str]]):
        self._count_request()
        from ..faults import injection as _flt

        body: Dict[str, Any] = {
            "status": "ok",
            "uptime_s": time.time() - self._t_start,
            "requests": self.requests,
            "faults_armed": _flt.ACTIVE is not None,
        }
        if self.health_fn is not None:
            body.update(self.health_fn())
        return "application/json", json.dumps(body).encode("utf-8")

    def _route_tracez(self, query: Dict[str, List[str]]):
        self._count_request()
        limit = _limit(query)
        if query.get("format", [None])[0] == "chrome":
            from .trace_export import chrome_trace

            matches: List[Dict[str, Any]] = []
            if self.match_exemplars is not None:
                matches = self.match_exemplars(limit)
            doc = chrome_trace(
                tracer=self.tracer, match_exemplars=matches, limit=limit
            )
            return "application/json", json.dumps(doc).encode("utf-8")
        kind = query.get("kind", ["span"])[0]
        if kind == "match":
            matches: List[Dict[str, Any]] = []
            if self.match_exemplars is not None:
                matches = self.match_exemplars(limit)
            body: Dict[str, Any] = {"kind": "match", "matches": matches}
        else:
            name = query.get("span", [None])[0]
            body = {
                "kind": "span",
                "spans": self.tracer.recent(limit, name=name),
            }
        return "application/json", json.dumps(body).encode("utf-8")

    def _route_explainz(self, query: Dict[str, List[str]]):
        """Read-only match-lineage surface (ISSUE 20): the attached
        `explain_fn`'s recent entries, newest first. ``?query=name``
        filters to one query's matches; ``?trace_id=`` to one trace.
        Pure ring reads -- never touches the data path."""
        self._count_request()
        limit = _limit(query)
        entries: List[Dict[str, Any]] = []
        if self.explain_fn is not None:
            entries = self.explain_fn(limit)
            qname = query.get("query", [None])[0]
            if qname is not None:
                entries = [e for e in entries if e.get("query") == qname]
            tid = query.get("trace_id", [None])[0]
            if tid is not None:
                entries = [e for e in entries if e.get("trace_id") == tid]
        body = {"kind": "explain", "matches": entries}
        return "application/json", json.dumps(body).encode("utf-8")

    def _route_profilez(self, query: Dict[str, List[str]]):
        """Arm an on-demand device xplane capture for ?secs=N (clamped)
        on a daemon thread, so the running pipeline profiles itself
        without a profiler attach. The capture wall also lands as a
        `device_trace` span (SpanTracer.device), so /tracez shows when a
        profile was taken. One capture at a time: a second request while
        armed replies busy instead of stacking profiler sessions."""
        self._count_request()
        try:
            secs = float(query.get("secs", ["1"])[0])
        except (TypeError, ValueError):
            secs = 1.0
        secs = max(0.0, min(secs, self.PROFILE_MAX_SECS))
        with self._profile_lock:
            if self._stop.is_set():
                # stop() already began: never arm a capture that would
                # outlive the plane (stop() joins under this same lock).
                body = {"armed": False, "stopping": True}
                return "application/json", json.dumps(body).encode("utf-8")
            if self._profile_thread is not None and self._profile_thread.is_alive():
                body = {"armed": False, "busy": True}
                return "application/json", json.dumps(body).encode("utf-8")
            log_dir = self.profile_dir
            if log_dir is None:
                import tempfile

                log_dir = tempfile.mkdtemp(prefix="cep-profilez-")

            def _capture() -> None:
                with self.tracer.device(log_dir):
                    self._stop.wait(secs)

            self._profile_thread = threading.Thread(
                target=_capture, name="kct-introspect-profile", daemon=True
            )
            self.profile_captures += 1
            self._profile_thread.start()
        body = {"armed": True, "secs": secs, "log_dir": log_dir}
        return "application/json", json.dumps(body).encode("utf-8")
